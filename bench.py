"""Benchmark: GBDT training throughput on the local accelerator.

Prints ONE JSON line per shape:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Default (the driver's contract) runs the HIGGS-like headline shape only;
set BENCH_SHAPE=epsilon|epsilon15|bosch|expo (or "all") to run the other
reference benchmark shapes; BENCH_SHAPE=multichip runs the 1->2->4->8
forced-host-device data-parallel scaling curve (Mrow-iters/s + per-pass
comm elements per device count — the MULTICHIP_*.json trajectory);
BENCH_SHAPE=serve runs the serving-tier suite (quantized f32/f16/int8
bulk throughput + open-loop sustained load with a mid-run hot swap +
eviction probe, written to BENCH_SERVE_r07.json);
BENCH_SHAPE=overload runs the serving overload-resilience gate
(scripts/overload_smoke.py: open-loop 2x-saturation shedding with
bounded admitted p99, circuit-breaker trip/recovery, single-flight
compile storm, persistent-compile-cache cold start — commits
OVERLOAD_r01.json).
BENCH_SHAPE=linear runs the piecewise-linear-leaves gate (regional
linear shape: at which iteration does a linear_tree booster reach the
constant-leaf run's final holdout l2; acceptance ratio <= 0.7, honest
trees/s overhead — commits LINEAR_r01.json).
BENCH_SHAPE=sweep runs the many-model vmapped-sweep gate (K=16 small
boosters trained as ONE XLA program via engine.train_sweep vs 16
sequential trains: amortized wall-clock speedup incl. all compiles +
per-model byte-identity — commits SWEEP_r01.json).
BENCH_SHAPE=quantgrad runs the quantized-gradient training gate (f32 vs
int16 vs int8 on a wide-histogram shape x max_bin=255 and a multiclass
shape: Mrow-iters/s, histogram-pass throughput ratio, scatter comm
bytes/pass under the hessian-channel elision, train-accuracy delta vs
f32, compile-cache hit/miss — commits QUANTGRAD_r01.json).
BENCH_SHAPE=lint runs the graftlint static-analysis gate
(scripts/lint_report.py: zero unsuppressed findings over lightgbm_tpu/
and scripts/, every suppression carrying a written reason, no stale
baseline entries — commits LINT_r01.json).
BENCH_SHAPE=export runs the exported-forest artifact gate
(scripts/export_smoke.py: f32/f16/int8 round-trip bit-identity,
corruption/version-skew/fingerprint refusal, and an import-blocked
child serving the artifact with the training stack absent, zero
steady-state retraces — commits EXPORT_r01.json).
BENCH_SHAPE=chaos runs the storage-fault-tolerance gate
(scripts/storage_chaos_smoke.py: training completes byte-identically
under injected checkpoint EIO/torn-write/slow-rename, run-log and
heartbeat write failures degrade to counted drops, and the ENOSPC
oldest-snapshot eviction hatch lands a save on a "full" disk —
commits CHAOS_r01.json).
BENCH_SHAPE=elastic runs the kill->shrink->resume supervisor cycle
(scripts/elastic_smoke.py: rank killed at W=4, wedged collective
detected by the watchdog, elastic resume at W'=2 then W'=1,
byte-identity vs the uninterrupted serial run — written to
ELASTIC_r01.json) (docs/GPU-Performance.md:74-116: Epsilon
400k x 2000 dense-wide, Bosch 1M x 968 sparse, Expo 11M x 700
categorical; row counts here are scaled to CI-time runs and the metric is
million row-iterations/sec, which is ~size-invariant).

BENCH_SHAPE=amortized runs the reference's ACTUAL published benchmark
protocol (docs/GPU-Performance.md:96-116): 500 iterations at the HIGGS
shape, metric = rows*iters/total wall INCLUDING dataset construction and
all compile time — the number the 15-iteration steady-state figure used
to overstate (round-4 verdict weak #2).

All shapes use the reference's published benchmark hyperparameters
(max_bin=63 [15 for the epsilon15 bin-width-discount variant],
num_leaves=255, lr=0.1, min_data_in_leaf=1, min_sum_hessian_in_leaf=100).

vs_baseline: the reference CPU implementation measured on this machine via
scripts/measure_baseline.py (which builds /root/reference out-of-tree) —
BENCH_BASELINE.json for the HIGGS shape (kept for round-over-round
comparability), BENCH_BASELINE_SHAPES.json for the rest; falls back to
1.0 (self-relative) if absent.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 2_000_000))
N_FEATURES = 28
N_ITERS = int(os.environ.get("BENCH_ITERS", 15))
NUM_LEAVES = 255
MAX_BIN = 63

REPO = os.path.dirname(os.path.abspath(__file__))

# backend-init retry schedule (relay-attached TPUs surface transient
# UNAVAILABLE during worker restarts; a one-shot probe turns a 30 s blip
# into a lost benchmark round)
BACKEND_RETRIES = max(1, int(os.environ.get("BENCH_BACKEND_RETRIES", 4)))
BACKEND_BACKOFF_S = float(os.environ.get("BENCH_BACKEND_BACKOFF", 5.0))

_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "failed to connect",
                      "Connection reset", "Socket closed")


def _init_backend_with_retry():
    """Initialize the jax backend, retrying transient relay outages with
    exponential backoff. On permanent outage: with BENCH_ALLOW_CPU=1 the
    benchmark re-execs itself onto the CPU backend (the same fallback
    the test suite uses — useful for sanity runs when the TPU relay is
    down; throughput numbers are then CPU numbers and say so); otherwise
    emit ONE machine-readable diagnostic JSON line (the driver's
    contract is a JSON line per metric — a raw traceback is unparseable)
    and exit nonzero."""
    import traceback
    if os.environ.get("BENCH_CPU_CHILD") == "1":
        # the CPU-fallback child: sitecustomize may pin jax_platforms via
        # jax.config (which ignores JAX_PLATFORMS), so override in-process
        # before any backend initializes — the __graft_entry__ dryrun's
        # proven pattern
        import jax
        jax.config.update("jax_platforms", "cpu")
        return [str(d) for d in jax.devices()]
    delay = BACKEND_BACKOFF_S
    last = None
    last_tb = ""
    attempt = 0
    for attempt in range(1, BACKEND_RETRIES + 1):
        try:
            import jax
            devs = jax.devices()
            return [str(d) for d in devs]
        except Exception as e:  # backend init failures are env-specific
            last = e
            last_tb = traceback.format_exc(limit=3)
            msg = str(e)
            transient = any(m in msg for m in _TRANSIENT_MARKERS)
            if not transient or attempt == BACKEND_RETRIES:
                break
            print(json.dumps({
                "event": "backend_retry", "attempt": attempt,
                "sleep_seconds": delay,
                "error": msg.splitlines()[0][:300] if msg else type(e).__name__,
            }), flush=True)
            time.sleep(delay)
            delay *= 2
    if os.environ.get("BENCH_ALLOW_CPU") == "1":
        # opt-in CPU fallback: re-exec in a child whose backend config is
        # clean (this process's failed accelerator init cannot be undone)
        import subprocess
        import sys
        print(json.dumps({
            "event": "backend_cpu_fallback",
            "error": str(last).splitlines()[0][:300] if str(last)
            else type(last).__name__,
            "attempts": attempt,
        }), flush=True)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_CPU_CHILD"] = "1"
        res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env)
        raise SystemExit(res.returncode)
    diag = {
        "metric": "bench_backend_unavailable",
        "value": None,
        "unit": None,
        "error": {
            "type": type(last).__name__,
            "message": str(last).splitlines()[0][:300] if str(last) else "",
            "attempts": attempt,
            "transient_markers": [m for m in _TRANSIENT_MARKERS
                                  if m in str(last)],
        },
        "detail": {"traceback_tail": last_tb.splitlines()[-3:]},
    }
    print(json.dumps(diag), flush=True)
    raise SystemExit(2)


def synth_higgs(n, f, seed=0):
    """Synthetic HIGGS-like: dense float features, binary label from a
    nonlinear score (matches HIGGS's structure: 28 kinematic features)."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    score = (X[:, 0] * 1.2 - X[:, 1] + 0.8 * X[:, 2] * X[:, 3]
             + 0.5 * np.abs(X[:, 4]) + 0.3 * X[:, 5] ** 2)
    y = (score + rng.logistic(size=n) > 0.5).astype(np.float32)
    return X, y


def synth_epsilon(n, f=2000, seed=1):
    """Epsilon-like: dense WIDE float features (Epsilon is 400k x 2000
    normalized dense). Exercises the group-block-tiled histogram pass."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(24)
    score = X[:, :24] @ w + 0.5 * X[:, 24] * X[:, 25]
    y = (score + rng.logistic(size=n) > 0.0).astype(np.float32)
    return X, y


def synth_bosch(n, f=968, seed=2):
    """Bosch-like: ~80% sparse with one-hot-style mutually-exclusive
    feature blocks (the structure EFB exists for, dataset.cpp:66-211)
    plus a tail of randomly-sparse numerics."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, f), np.float32)
    # 700 features in exclusive blocks of 10: each row activates exactly
    # one feature of each block (one-hot-encoded categoricals)
    n_blocks = 70
    for b in range(n_blocks):
        pick = rng.randint(0, 10, size=n)
        vals = rng.rand(n).astype(np.float32) + 0.1
        X[np.arange(n), b * 10 + pick] = vals
    # remaining features: 80% zeros random sparse
    f_rest = f - n_blocks * 10
    R = rng.randn(n, f_rest).astype(np.float32)
    R[rng.rand(n, f_rest) < 0.8] = 0.0
    X[:, n_blocks * 10:] = R
    score = (X[:, 0] * 2.0 - X[:, 10] + X[:, 700] - 0.5 * X[:, 701]
             + X[:, 20] * X[:, 702])
    y = (score + 0.5 * rng.logistic(size=n) > 0.3).astype(np.float32)
    return X, y


def synth_multiclass(n, f=28, k=5, seed=4):
    """Multiclass shape (no reference-published analogue; exercises the
    one-program-per-iteration vmap'd class growth, gbdt.cpp:410-462)."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    centers = rng.randn(k, 6) * 1.5
    d = ((X[:, None, :6] - centers[None]) ** 2).sum(-1)
    y = np.argmin(d + rng.gumbel(size=(n, k)), axis=1).astype(np.float32)
    return X, y


def synth_expo(n, seed=3):
    """Expo-like: mixed categorical + numeric (the reference one-hot
    encodes Expo to 700 binary columns; the native-categorical path is
    the TPU framework's analogue). 8 categoricals (cardinality 12..96)
    + 32 numerics; label depends on categories nonlinearly."""
    rng = np.random.RandomState(seed)
    cards = [12, 24, 24, 48, 48, 64, 96, 96]
    cats = [rng.randint(0, c, size=n) for c in cards]
    Xn = rng.randn(n, 32).astype(np.float32)
    X = np.column_stack([np.asarray(c, np.float32) for c in cats] + [Xn])
    score = (np.sin(cats[0] * 1.7) + (cats[3] % 5 == 0) * 1.5
             + np.cos(cats[6] * 0.4) + Xn[:, 0] - 0.5 * Xn[:, 1])
    y = (score + rng.logistic(size=n) > 0.5).astype(np.float32)
    return X, y, list(range(8))


# name -> (rows, builder() -> (X, y[, categorical_idx]), max_bin)
SHAPES = {
    "higgs": (N_ROWS, lambda n: synth_higgs(n, N_FEATURES), MAX_BIN),
    "epsilon": (int(os.environ.get("BENCH_EPSILON_ROWS", 200_000)),
                synth_epsilon, 63),
    "epsilon15": (int(os.environ.get("BENCH_EPSILON_ROWS", 200_000)),
                  synth_epsilon, 15),
    "bosch": (int(os.environ.get("BENCH_BOSCH_ROWS", 500_000)),
              synth_bosch, 63),
    "expo": (int(os.environ.get("BENCH_EXPO_ROWS", 1_000_000)),
             synth_expo, 63),
    "multiclass": (int(os.environ.get("BENCH_MC_ROWS", 500_000)),
                   synth_multiclass, 63),
}


def _bench_cache_dir() -> str:
    """Shared persistent-XLA-cache dir for repeated-shape bench legs
    (BENCH_COMPILE_CACHE_DIR to pin; BENCH_NO_COMPILE_CACHE=1 to opt
    out). Default is a STABLE path under the system temp dir, so
    back-to-back bench invocations of the same shape skip the 29-81s
    wide-shape compile tails instead of paying them into every
    amortized number."""
    import tempfile
    d = os.environ.get("BENCH_COMPILE_CACHE_DIR") or os.path.join(
        tempfile.gettempdir(), "lgbm_tpu_bench_xla_cache")
    os.makedirs(d, exist_ok=True)
    return d


def _cache_entries(d: str) -> int:
    total = 0
    for _, _, files in os.walk(d):
        total += len(files)
    return total


def _baseline_for(shape: str):
    if shape == "higgs":
        path = os.path.join(REPO, "BENCH_BASELINE.json")
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh).get("mrows_per_sec")
        return None
    path = os.path.join(REPO, "BENCH_BASELINE_SHAPES.json")
    if os.path.exists(path):
        with open(path) as fh:
            entry = json.load(fh).get(shape)
        if entry:
            return entry.get("mrows_per_sec")
    return None


def run_shape(shape: str) -> dict:
    import lightgbm_tpu as lgb

    n_rows, builder, max_bin = SHAPES[shape]
    built = builder(n_rows)
    cat_idx = None
    if len(built) == 3:
        X, y, cat_idx = built
    else:
        X, y = built
    params = {
        "objective": "binary", "metric": "auc", "verbose": -1,
        "max_bin": max_bin, "num_leaves": NUM_LEAVES,
        "learning_rate": 0.1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0,
    }
    if cat_idx is not None:
        params["categorical_feature"] = cat_idx
    cache_dir = None
    if os.environ.get("BENCH_NO_COMPILE_CACHE") != "1":
        cache_dir = _bench_cache_dir()
        params["tpu_compile_cache_dir"] = cache_dir
        cache_before = _cache_entries(cache_dir)
    # no per-shape schedule knobs here: batch_k / subtraction / compaction
    # are auto-selected by shape inside boosting/gbdt.py (r4 verdict weak
    # #4 — the engine picks its own schedule, not the benchmark harness)
    if shape == "multiclass":
        params.update(objective="multiclass", num_class=5,
                      metric="multi_logloss")
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()

    # warmup: compile the grower (first tree)
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=1, verbose_eval=False)
    compile_time = time.time() - t0

    # per-iteration wall times via callback; the first timed iteration
    # carries the per-run jit trace (the reference C++ has no compile
    # analogue and its published benchmarks run 500 iters, where one
    # trace amortizes to noise) — report BOTH with/without it
    iter_times = []
    last = [None]

    def _timer(env):
        now = time.time()
        if last[0] is not None:
            iter_times.append(now - last[0])
        last[0] = now

    t0 = time.time()
    booster = lgb.train(dict(params), ds, num_boost_round=N_ITERS,
                        verbose_eval=False, callbacks=[_timer])
    train_time = time.time() - t0

    steady = iter_times[1:] if len(iter_times) > 2 else iter_times
    steady_time = sum(steady) / len(steady) if steady \
        else train_time / N_ITERS
    rows_per_sec = n_rows / steady_time
    value = rows_per_sec / 1e6  # million row-iterations per second
    value_incl_trace = n_rows * N_ITERS / train_time / 1e6

    baseline = _baseline_for(shape)
    vs_baseline = (value / baseline) if baseline else 1.0

    detail = {
        "backend": "cpu-fallback"
        if os.environ.get("BENCH_CPU_CHILD") == "1" else "default",
        "rows": n_rows, "features": int(X.shape[1]), "iters": N_ITERS,
        "num_leaves": NUM_LEAVES, "max_bin": max_bin,
        "categorical": len(cat_idx) if cat_idx else 0,
        "train_seconds": round(train_time, 3),
        "compile_seconds": round(compile_time, 3),
        "steady_seconds_per_iter": round(steady_time, 4),
        "mrow_iters_incl_trace": round(value_incl_trace, 4),
    }
    if cache_dir is not None:
        # compile-cache economics: zero new entries means every program
        # this shape needed was already on disk (a repeated-shape run)
        # and compile_seconds above was a file read, not a compile
        new_entries = _cache_entries(cache_dir) - cache_before
        detail["compile_cache"] = {
            "dir": cache_dir, "entries_before": cache_before,
            "new_entries": new_entries, "hit": new_entries == 0,
        }
    # pass economics (serial pipelined path records them per tree): the
    # gather-compacted contraction shows up as rows_contracted well
    # under passes * rows — the ratio is the realized late-tree discount
    pass_log = getattr(getattr(booster, "_inner", None), "pass_log", None)
    if pass_log:
        tail = pass_log[-min(5, len(pass_log)):]
        passes = sum(p[0] for p in tail) / len(tail)
        rows_c = sum(p[2] for p in tail if len(p) > 2) / len(tail)
        detail["passes_per_tree"] = round(passes, 1)
        if rows_c:
            detail["rows_contracted_per_tree"] = round(rows_c)
            detail["full_pass_equivalent_rows"] = round(passes * n_rows)
            detail["contraction_row_discount"] = round(
                passes * n_rows / max(rows_c, 1.0), 3)

    return {
        "metric": f"{shape}_like_train_throughput",
        "value": round(value, 4),
        "unit": "mrow_iters/s",
        "vs_baseline": round(vs_baseline, 4),
        "detail": detail,
    }


def run_amortized(rows=None, iters=None) -> dict:
    """The reference's published 500-iteration protocol at the HIGGS
    shape; wall includes construct + compile (a C++ binary pays neither,
    so they count against us — docs/GPU-Performance.md:96-116)."""
    import lightgbm_tpu as lgb

    rows = rows or int(os.environ.get("BENCH_AMORT_ROWS", N_ROWS))
    iters = iters or int(os.environ.get("BENCH_AMORT_ITERS", 500))
    X, y = synth_higgs(rows, N_FEATURES)
    params = {
        "objective": "binary", "metric": "auc", "verbose": -1,
        "max_bin": MAX_BIN, "num_leaves": NUM_LEAVES,
        "learning_rate": 0.1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0,
    }
    t0 = time.time()
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()
    lgb.train(dict(params), ds, num_boost_round=iters, verbose_eval=False)
    wall = time.time() - t0
    value = rows * iters / wall / 1e6

    base = None
    path = os.path.join(REPO, "BENCH_BASELINE_AMORTIZED.json")
    if os.path.exists(path):
        with open(path) as fh:
            base = json.load(fh).get("mrow_iters_per_s")
    return {
        "metric": "higgs_500iter_amortized_train_throughput",
        "value": round(value, 4),
        "unit": "mrow_iters/s",
        "vs_baseline": round(value / base, 4) if base else 1.0,
        "detail": {"rows": rows, "iters": iters,
                   "wall_seconds_incl_construct_compile": round(wall, 1),
                   "backend": "cpu-fallback"
                   if os.environ.get("BENCH_CPU_CHILD") == "1"
                   else "default"},
    }


def _ingest_child(mode: str, path: str, rows: int) -> None:
    """One measured construction in a FRESH process (BENCH_INGEST_CHILD):
    ru_maxrss is a process-lifetime high-water mark, so streamed and
    in-memory construction must not share an address space. Prints one
    JSON line {mode, wall_seconds, mrows_per_s, peak_rss_mb}."""
    import resource

    import lightgbm_tpu as lgb
    params = {"max_bin": MAX_BIN, "verbose": -1}
    if mode == "inmem":
        params["tpu_ingest"] = False
    t0 = time.time()
    ds = lgb.Dataset(path, params=params)
    ds.construct()
    wall = time.time() - t0
    assert ds._inner.num_data == rows, (ds._inner.num_data, rows)
    peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    print(json.dumps({
        "mode": mode, "wall_seconds": round(wall, 3),
        "mrows_per_s": round(rows / wall / 1e6, 4),
        "peak_rss_mb": round(peak_kb / 1024.0, 1),
        "binned_shape": list(ds._inner.binned.shape),
    }), flush=True)


def run_ingest() -> list:
    """Ingest benchmarks (BENCH_SHAPE=ingest): streamed two-pass file
    construction vs the in-memory load-then-bin path, each in its own
    child process — Mrows/s plus peak RSS, so the memory claim of the
    streaming subsystem (no raw float matrix) is a measured number, not
    a design note."""
    import subprocess
    import sys
    import tempfile

    rows = int(os.environ.get("BENCH_INGEST_ROWS", 400_000))
    X, y = synth_higgs(rows, N_FEATURES)
    tmp = tempfile.mkdtemp(prefix="bench_ingest_")
    path = os.path.join(tmp, "ingest.tsv")
    np.savetxt(path, np.column_stack([y, X]), delimiter="\t", fmt="%.7g")
    raw_mb = X.nbytes / 1e6
    del X, y

    out = []
    results = {}
    for mode in ("streamed", "inmem"):
        env = dict(os.environ)
        env["BENCH_INGEST_CHILD"] = mode
        env["BENCH_INGEST_PATH"] = path
        env["BENCH_INGEST_ROWS"] = str(rows)
        res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True)
        line = next((ln for ln in res.stdout.splitlines()
                     if ln.startswith("{")), None)
        if res.returncode != 0 or line is None:
            out.append({"metric": f"ingest_{mode}_construct", "value": None,
                        "unit": "mrows/s",
                        "error": (res.stdout + res.stderr)[-400:]})
            continue
        results[mode] = json.loads(line)
    for mode, rec in results.items():
        detail = {"rows": rows, "features": N_FEATURES,
                  "raw_float64_mb": round(raw_mb, 1),
                  "peak_rss_mb": rec["peak_rss_mb"],
                  "wall_seconds": rec["wall_seconds"]}
        if len(results) == 2:
            other = results["inmem" if mode == "streamed" else "streamed"]
            detail["peak_rss_vs_other_mb"] = other["peak_rss_mb"]
        out.append({"metric": f"ingest_{mode}_construct",
                    "value": rec["mrows_per_s"], "unit": "mrows/s",
                    "vs_baseline": 1.0, "detail": detail})
    try:
        os.remove(path)
        os.rmdir(tmp)
    except OSError:
        pass
    return out


def run_predict() -> list:
    """Serving predict benchmarks (BENCH_SHAPE=predict): bulk throughput
    over one large matrix and repeated small-batch latency — the two
    serving steady states. The small-batch detail carries the speedup
    over the per-call-restack seed behavior (tpu_predict_cache=false +
    no buckets + no pipeline), the number the device-resident
    CompiledForest cache exists for."""
    import lightgbm_tpu as lgb

    train_rows = int(os.environ.get("BENCH_PREDICT_TRAIN_ROWS", 50_000))
    trees = int(os.environ.get("BENCH_PREDICT_TREES", 500))
    bulk_rows = int(os.environ.get("BENCH_PREDICT_ROWS", 1_000_000))
    reps = int(os.environ.get("BENCH_PREDICT_REPS", 100))
    batch = int(os.environ.get("BENCH_PREDICT_BATCH", 8))

    X, y = synth_higgs(train_rows, N_FEATURES)
    params = {
        "objective": "binary", "verbose": -1, "max_bin": MAX_BIN,
        "num_leaves": 31, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0,
    }
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()
    t0 = time.time()
    booster = lgb.train(dict(params), ds, num_boost_round=trees,
                        verbose_eval=False)
    train_s = time.time() - t0
    model_str = booster.model_to_string()
    num_trees = booster.num_trees()

    out = []
    # ---- bulk throughput ------------------------------------------------
    Xb, _ = synth_higgs(bulk_rows, N_FEATURES, seed=7)
    predictor = booster.serving_predictor(raw_score=True)
    # one full untimed pass: compiles every bucket program the bulk scan
    # uses (including the full-chunk bucket) + stacks the forest, so the
    # timed pass is pure steady-state dispatch
    predictor.predict(Xb)
    t0 = time.time()
    predictor.predict(Xb)
    bulk_s = time.time() - t0
    out.append({
        "metric": "predict_bulk_throughput",
        "value": round(bulk_rows / bulk_s / 1e6, 4),
        "unit": "mrows/s",
        "vs_baseline": 1.0,
        "detail": {"rows": bulk_rows, "trees": num_trees,
                   "train_seconds": round(train_s, 1),
                   "bulk_seconds": round(bulk_s, 3)},
    })

    # ---- repeated small-batch latency ----------------------------------
    predictor.warmup(max_rows=max(batch, 16))
    lats = []
    for i in range(reps):
        sl = Xb[(i * batch) % 4096:(i * batch) % 4096 + batch]
        t0 = time.perf_counter()
        predictor.predict(sl)
        lats.append(time.perf_counter() - t0)
    lats.sort()
    p50 = lats[len(lats) // 2]

    # seed behavior: restack + retrace per call
    seed_booster = lgb.Booster(model_str=model_str, params={
        "tpu_predict_cache": "false", "tpu_predict_bucket_min": 0,
        "tpu_predict_pipeline": "false"})
    seed_reps = max(3, min(10, reps // 10))
    seed_lats = []
    for i in range(seed_reps):
        sl = Xb[i * batch:(i + 1) * batch]
        t0 = time.perf_counter()
        seed_booster.predict(sl, raw_score=True)
        seed_lats.append(time.perf_counter() - t0)
    seed_lats.sort()
    seed_p50 = seed_lats[len(seed_lats) // 2]
    out.append({
        "metric": "predict_small_batch_p50_latency",
        "value": round(p50 * 1e3, 4),
        "unit": "ms",
        "vs_baseline": 1.0,
        "detail": {"batch_rows": batch, "reps": reps, "trees": num_trees,
                   "p50_seed_percall_restack_ms": round(seed_p50 * 1e3, 3),
                   "speedup_vs_percall_restack":
                       round(seed_p50 / max(p50, 1e-12), 2),
                   "restacks": predictor.stats().get("stack_restacks")},
    })
    return out


def run_serve() -> list:
    """Serving-tier benchmarks (BENCH_SHAPE=serve) — the heavy-traffic
    numbers the multi-tenant registry exists for:

    (1) quantized bulk throughput: f32 vs f16 vs int8 Mrows/s through
        the 500-tree serving stacks (accuracy gate at the default
        tolerance — a lossy layout would abort the bench);
    (2) open-loop sustained load against a ModelRegistry: Poisson
        arrivals at a target QPS, mixed single-row submit() /
        small-batch predict() traffic, one mid-run hot swap to a
        freshly trained model — p50/p99 arrival-to-completion latency,
        achieved QPS, and a zero-dropped-requests gate;
    (3) eviction probe: two resident models under a deliberately tight
        stack budget, proving budget enforcement stays correct (both
        models keep serving bit-identical results while stacks churn).

    Also writes the whole record to BENCH_SERVE_OUT (default
    BENCH_SERVE_r07.json next to this file) so serving regressions are
    tracked round-over-round like the training shapes."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry

    trees = int(os.environ.get("BENCH_SERVE_TREES", 500))
    train_rows = int(os.environ.get("BENCH_SERVE_TRAIN_ROWS", 6000))
    bulk_rows = int(os.environ.get("BENCH_SERVE_ROWS", 262_144))
    qps = float(os.environ.get("BENCH_SERVE_QPS", 200.0))
    seconds = float(os.environ.get("BENCH_SERVE_SECONDS", 10.0))
    serve_quant = os.environ.get("BENCH_SERVE_QUANTIZE", "f16")

    X, y = synth_higgs(train_rows, N_FEATURES)
    params = {
        "objective": "binary", "verbose": -1, "max_bin": MAX_BIN,
        "num_leaves": 31, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0,
    }
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()
    t0 = time.time()
    booster_a = lgb.train(dict(params), ds, num_boost_round=trees,
                          verbose_eval=False)
    train_s = time.time() - t0
    swap_trees = max(20, trees // 10)
    booster_b = lgb.train(dict(params), ds, num_boost_round=swap_trees,
                          verbose_eval=False)
    model_str = booster_a.model_to_string()
    num_trees = booster_a.num_trees()

    out = []
    backend = "cpu-fallback" if os.environ.get("BENCH_CPU_CHILD") == "1" \
        else "default"

    # ---- (1) quantized bulk throughput ---------------------------------
    Xb, _ = synth_higgs(bulk_rows, N_FEATURES, seed=7)
    bulk = {}
    for mode in ("none", "f16", "int8"):
        b = lgb.Booster(model_str=model_str,
                        params={"tpu_predict_quantize": mode})
        predictor = b.serving_predictor(raw_score=True)
        predictor.predict(Xb)        # compile + stack + accuracy gate
        t0 = time.time()
        predictor.predict(Xb)
        wall = time.time() - t0
        total_cap = b._inner.num_trees()
        gate = b._inner._compiled_forest.gate_delta(
            ("value", total_cap, 1, mode)) if mode != "none" else 0.0
        bulk[mode] = {
            "mrows_per_s": round(bulk_rows / wall / 1e6, 4),
            "seconds": round(wall, 3),
            "gate_delta": None if gate is None else round(gate, 8),
        }
    for mode, rec in bulk.items():
        detail = {"rows": bulk_rows, "trees": num_trees,
                  "backend": backend, "gate_delta": rec["gate_delta"],
                  "train_seconds": round(train_s, 1)}
        if mode != "none":
            detail["speedup_vs_f32"] = round(
                rec["mrows_per_s"] / max(bulk["none"]["mrows_per_s"], 1e-9),
                3)
        out.append({
            "metric": "serve_bulk_throughput_%s"
                      % ("f32" if mode == "none" else mode),
            "value": rec["mrows_per_s"],
            "unit": "mrows/s", "vs_baseline": 1.0, "detail": detail,
        })

    # ---- (2) open-loop sustained load + mid-run hot swap ---------------
    rng = np.random.RandomState(11)
    reg = ModelRegistry(warmup_rows=64)
    # serve under the quantized layout the tier is built for
    reg_a = lgb.Booster(model_str=model_str,
                        params={"tpu_predict_quantize": serve_quant})
    reg.publish("main", reg_a)
    reg.submit("main", Xb[0]).result(timeout=60)   # settle the batcher

    n_req = max(1, int(qps * seconds))
    gaps = rng.exponential(1.0 / qps, size=n_req)
    arrivals = np.cumsum(gaps)
    is_batch = rng.rand(n_req) < 0.15
    lat_lock = threading.Lock()
    lats, dropped = [], [0]
    pool = ThreadPoolExecutor(max_workers=8)
    swap_at = arrivals[-1] / 2.0
    swap_state = {"done": False, "wall": None, "published_at": None}

    def record(arrival_abs, err=None):
        dt = time.perf_counter() - arrival_abs
        with lat_lock:
            if err is None:
                lats.append(dt)
            else:
                dropped[0] += 1

    # the incoming version serves under the SAME quantized layout, so
    # post-swap traffic measures the layout, not an f32 regression; the
    # accuracy gate is settled on real rows BEFORE publishing (the
    # operational pattern: validate the candidate on real data, then
    # promote) so the mid-load swap measures swap mechanics, not the
    # one-time calibration compile
    swap_booster = lgb.Booster(model_str=booster_b.model_to_string(),
                               params={"tpu_predict_quantize": serve_quant})
    swap_booster.predict(Xb[:256], raw_score=True)

    def do_swap():
        t_sw = time.perf_counter()
        reg.publish("main", swap_booster)
        swap_state["wall"] = time.perf_counter() - t_sw
        swap_state["published_at"] = time.perf_counter()

    def do_batch(arrival_abs, lo):
        try:
            reg.predict("main", Xb[lo:lo + 8])
            record(arrival_abs)
        except Exception:
            record(arrival_abs, err=True)

    start = time.perf_counter()
    for i in range(n_req):
        target = start + arrivals[i]
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        if not swap_state["done"] and arrivals[i] >= swap_at:
            swap_state["done"] = True
            pool.submit(do_swap)
        arrival_abs = time.perf_counter()
        if is_batch[i]:
            pool.submit(do_batch, arrival_abs, int(i * 8 % 4096))
        else:
            fut = reg.submit("main", Xb[i % 4096])
            fut.add_done_callback(
                lambda f, a=arrival_abs: record(a, err=f.exception()))
    pool.shutdown(wait=True)
    deadline = time.time() + 60
    while time.time() < deadline:
        with lat_lock:
            if len(lats) + dropped[0] >= n_req:
                break
        time.sleep(0.01)
    wall = time.perf_counter() - start
    reg_stats = reg.stats()
    reg.close()

    # snapshot under the lock: past the deadline, straggler callbacks
    # may still be appending while we aggregate
    with lat_lock:
        done_lats = sorted(lats)
        n_dropped = int(dropped[0])
    p50 = done_lats[len(done_lats) // 2] if done_lats else None
    p99 = done_lats[int(len(done_lats) * 0.99)] if done_lats else None
    serve_rec = {
        "metric": "serve_sustained_load",
        "value": round(len(done_lats) / wall, 2),
        "unit": "qps",
        "vs_baseline": 1.0,
        "detail": {
            "backend": backend, "quantize": serve_quant,
            "target_qps": qps, "seconds": round(wall, 2),
            "requests": n_req, "completed": len(done_lats),
            "dropped": n_dropped,
            "batch_fraction": 0.15, "batch_rows": 8,
            "p50_latency_ms": round(p50 * 1e3, 3) if p50 else None,
            "p99_latency_ms": round(p99 * 1e3, 3) if p99 else None,
            "hot_swap_wall_seconds": round(swap_state["wall"], 3)
            if swap_state["wall"] else None,
            "swaps": reg_stats["swaps"],
            "trees_before_after": [num_trees, booster_b.num_trees()],
        },
    }
    out.append(serve_rec)

    # ---- (3) eviction probe under a tight budget -----------------------
    small = lgb.Booster(model_str=booster_b.model_to_string())
    reg2 = ModelRegistry(budget_mb=float(
        os.environ.get("BENCH_SERVE_BUDGET_MB", 0.05)), warmup_rows=0)
    reg2.publish("a", lgb.Booster(model_str=model_str))
    reg2.publish("b", small)
    probe = Xb[:64]
    for _ in range(3):
        reg2.predict("a", probe)
        reg2.predict("b", probe)
    ev_stats = reg2.stats()
    reg2.close()
    out.append({
        "metric": "serve_eviction_probe",
        "value": ev_stats["evictions"],
        "unit": "evictions",
        "vs_baseline": 1.0,
        "detail": {"budget_bytes": ev_stats["budget_bytes"],
                   "stack_bytes": ev_stats["stack_bytes"],
                   "resident_models": ev_stats["resident_models"],
                   "requests": ev_stats["requests"]},
    })

    out_path = os.environ.get(
        "BENCH_SERVE_OUT", os.path.join(REPO, "BENCH_SERVE_r07.json"))
    try:
        with open(out_path, "w") as fh:
            json.dump({"shape": "serve", "entries": out}, fh, indent=1)
    except OSError:
        pass
    return out


def _multichip_child(n_devices: int) -> None:
    """One device count of the scaling curve, in a FRESH process (the
    forced host-device count only applies before backend init). Trains
    the data-parallel learner (even at 1 device, so the curve is
    apples-to-apples) and prints one JSON line with throughput + the
    per-tree comm-elements the scatter schedule exists to shrink."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    import lightgbm_tpu as lgb

    rows = int(os.environ.get("BENCH_MULTICHIP_ROWS", 200_000))
    iters = int(os.environ.get("BENCH_MULTICHIP_ITERS", 8))
    reduce_mode = os.environ.get("BENCH_MULTICHIP_REDUCE", "scatter")
    assert len(jax.devices()) >= n_devices
    X, y = synth_higgs(rows, N_FEATURES)
    params = {
        "objective": "binary", "verbose": -1, "max_bin": MAX_BIN,
        "num_leaves": 63, "learning_rate": 0.1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0, "tree_learner": "data",
        "tpu_hist_reduce": reduce_mode,
    }
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=1, verbose_eval=False)
    compile_s = time.time() - t0
    t0 = time.time()
    booster = lgb.train(dict(params), ds, num_boost_round=iters,
                        verbose_eval=False)
    booster.model_to_string()  # drain the pipeline before stopping the clock
    wall = time.time() - t0
    inner = booster._inner
    plog = getattr(inner, "pass_log", None) or []
    comm = (sum(p[3] for p in plog if len(p) > 3) / len(plog)) if plog \
        else 0.0
    passes = (sum(p[0] for p in plog) / len(plog)) if plog else 0.0
    sched = getattr(inner, "_schedule_info", {})
    print(json.dumps({
        "n_devices": n_devices,
        "mrow_iters_per_s": round(rows * iters / wall / 1e6, 4),
        "wall_seconds": round(wall, 2),
        "compile_seconds": round(compile_s, 2),
        "rows": rows, "iters": iters,
        "hist_reduce": sched.get("hist_reduce"),
        "owned_groups": sched.get("owned_groups"),
        "groups": sched.get("groups"),
        "comm_elems_per_tree": round(comm),
        "comm_elems_per_pass": round(comm / passes) if passes else 0,
        "passes_per_tree": round(passes, 1),
    }), flush=True)


def run_multichip() -> list:
    """Scaling curve (BENCH_SHAPE=multichip): the data-parallel learner
    at 1 -> 2 -> 4 -> 8 forced host CPU devices, one child process per
    device count, Mrow-iters/s + per-pass comm elements each. Feeds the
    committed MULTICHIP_*.json trajectory so scaling regressions (and
    the collective-volume economics of tpu_hist_reduce=scatter) are
    visible round over round."""
    import subprocess
    import sys

    counts = [int(d) for d in os.environ.get(
        "BENCH_MULTICHIP_DEVICES", "1,2,4,8").replace(",", " ").split()]
    per_dev = {}
    out = []
    for d in counts:
        env = dict(os.environ)
        env["BENCH_MULTICHIP_CHILD"] = str(d)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + f" --xla_force_host_platform_device_count={d}"
                            ).strip()
        try:
            res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                 env=env, capture_output=True, text=True,
                                 timeout=float(os.environ.get(
                                     "BENCH_MULTICHIP_TIMEOUT", 1200)))
            rc, out_text = res.returncode, res.stdout + res.stderr
        except subprocess.TimeoutExpired as exc:
            # one wedged device count must not abort the curve — the
            # driver's contract is one JSON record per shape either way
            rc = 124
            out_text = "timeout: " + str(exc)
        line = next((ln for ln in out_text.splitlines()
                     if ln.startswith("{")), None)
        if rc != 0 or line is None:
            out.append({"metric": f"multichip_{d}dev_train_throughput",
                        "value": None, "unit": "mrow_iters/s",
                        "error": out_text[-400:]})
            continue
        rec = json.loads(line)
        per_dev[d] = rec
        out.append({
            "metric": f"multichip_{d}dev_train_throughput",
            "value": rec["mrow_iters_per_s"],
            "unit": "mrow_iters/s",
            "vs_baseline": 1.0,
            "detail": rec,
        })
    base = per_dev.get(counts[0], {}).get("mrow_iters_per_s")
    if base:
        for d, rec in per_dev.items():
            rec["speedup_vs_1dev"] = round(rec["mrow_iters_per_s"] / base, 3)
        best = max(per_dev.values(), key=lambda r: r["mrow_iters_per_s"])
        out.append({
            "metric": "multichip_scaling_best_speedup",
            "value": best.get("speedup_vs_1dev"),
            "unit": "x_vs_1dev",
            "vs_baseline": 1.0,
            "detail": {"best_n_devices": best["n_devices"],
                       "devices_measured": counts,
                       "per_device": {str(d): per_dev[d] for d in per_dev}},
        })
    return out


def _sweep_bench_config():
    k_models = int(os.environ.get("BENCH_SWEEP_MODELS", 16))
    rows = int(os.environ.get("BENCH_SWEEP_ROWS", 256))
    iters = int(os.environ.get("BENCH_SWEEP_ITERS", 20))
    feats = int(os.environ.get("BENCH_SWEEP_FEATURES", 28))
    # sibling subtraction stays off on BOTH sides: K per-model
    # subtraction caches thrash the vmapped while-loop carry on small
    # shapes, and byte-identity requires the two sides to share one
    # schedule (the knob is config-validated identical here)
    base = {
        "objective": "binary", "verbosity": -1, "max_bin": MAX_BIN,
        "num_leaves": 31, "min_data_in_leaf": 10, "bagging_freq": 1,
        "tpu_hist_subtract": False,
    }
    plist = [dict(base, learning_rate=0.05 + 0.01 * k,
                  lambda_l2=0.25 * (1 + k), bagging_fraction=0.8,
                  bagging_seed=k)
             for k in range(k_models)]
    return k_models, rows, iters, feats, base, plist


def _sweep_child():
    """One sequential train of the process-per-train baseline: a fresh
    process imports the stack, rebuilds the (deterministic) dataset,
    trains ONE config, and writes its model text for the byte-identity
    check. This is the sweep workflow as it runs today — a shell loop
    over configs — so each train pays its own interpreter + trace."""
    import lightgbm_tpu as lgb
    idx = int(os.environ["BENCH_SWEEP_CHILD"])
    _, rows, iters, feats, base, plist = _sweep_bench_config()
    X, y = synth_higgs(rows, feats, seed=5)
    ds = lgb.Dataset(X, y, params=dict(base))
    booster = lgb.train(dict(plist[idx]), ds, num_boost_round=iters,
                        verbose_eval=False)
    with open(os.environ["BENCH_SWEEP_MODEL_OUT"], "w") as fh:
        fh.write(booster.model_to_string())


def run_sweep() -> list:
    """Many-model sweep gate (BENCH_SHAPE=sweep): train K=16 small
    boosters — a per-segment fleet shape: tiny rows, real trees — as
    ONE vmapped sweep (engine.train_sweep, one compiled program
    amortized over the fleet) against BOTH sequential baselines:

      (a) process-per-train: 16 child processes, one config each — the
          sweep workflow as it actually runs today (a shell loop over
          configs), where every train pays its own interpreter start,
          dataset build, and trace. The >= 4x acceptance gate is
          measured here.
      (b) warm in-process: 16 engine.train calls in ONE process
          sharing the jit cache — the strongest sequential baseline.
          Each distinct lambda_l2 still retraces the serial grower
          (static knob there, traced [K] for the sweep). On CPU this
          leg under-states the sweep win structurally: the vmapped
          pass pays real 16x FLOPs + batched-op overhead that the
          MXU's 128-lane tile floor absorbs on TPU, capping the
          measured CPU ratio near ~3x — recorded honestly, like the
          CPU-collective-bound 8-way multichip number.

    Every sweep model's trees must be byte-identical to BOTH baselines'
    (model_to_string equality). Writes the whole record to
    BENCH_SWEEP_OUT (default SWEEP_r01.json next to this file)."""
    import subprocess
    import sys
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.engine import train_sweep
    from lightgbm_tpu.serving import ModelRegistry

    k_models, rows, iters, feats, base, plist = _sweep_bench_config()
    backend = "cpu-fallback" if os.environ.get("BENCH_CPU_CHILD") == "1" \
        else "default"

    X, y = synth_higgs(rows, feats, seed=5)
    ds = lgb.Dataset(X, y, params=dict(base))
    ds.construct()

    # (a) process-per-train baseline
    child_walls = []
    child_texts = []
    with tempfile.TemporaryDirectory() as tmp:
        for k in range(k_models):
            out = os.path.join(tmp, f"model_{k}.txt")
            env = dict(os.environ, BENCH_SWEEP_CHILD=str(k),
                       BENCH_SWEEP_MODEL_OUT=out)
            ti = time.time()
            res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                 env=env, capture_output=True, text=True,
                                 timeout=600)
            child_walls.append(round(time.time() - ti, 3))
            if res.returncode != 0:
                raise RuntimeError("sweep child %d failed: %s"
                                   % (k, res.stderr[-500:]))
            with open(out) as fh:
                child_texts.append(fh.read())
    procs_s = float(sum(child_walls))

    # (b) warm in-process baseline (shared jit cache across the trains)
    t0 = time.time()
    serial_models = []
    serial_walls = []
    for p in plist:
        ti = time.time()
        b = lgb.train(dict(p), ds, num_boost_round=iters,
                      verbose_eval=False)
        serial_walls.append(round(time.time() - ti, 3))
        serial_models.append(b)
    seq_s = time.time() - t0

    # sweep leg: one train_sweep call (the baselines do not publish
    # anything, so registry landing is timed separately below)
    t0 = time.time()
    sweep_models = train_sweep([dict(p) for p in plist], ds,
                               num_boost_round=iters)
    sweep_s = time.time() - t0

    reg = ModelRegistry(warmup_rows=0)
    t0 = time.time()
    reg.publish_many({f"sweep/{k}": b
                      for k, b in enumerate(sweep_models)})
    publish_s = time.time() - t0
    published = sorted(reg.models())
    reg.close()

    identical = [serial_models[k].model_to_string()
                 == sweep_models[k].model_to_string()
                 == child_texts[k]
                 for k in range(k_models)]
    speedup_procs = procs_s / max(sweep_s, 1e-9)
    speedup_warm = seq_s / max(sweep_s, 1e-9)
    detail = {
        "models": k_models, "rows": rows, "iterations": iters,
        "features": feats, "num_leaves": base["num_leaves"],
        "max_bin": base["max_bin"], "backend": backend,
        "process_per_train_seconds": round(procs_s, 2),
        "process_per_train_walls": child_walls,
        "warm_inprocess_seconds": round(seq_s, 2),
        "warm_inprocess_per_train": serial_walls,
        "sweep_seconds": round(sweep_s, 2),
        "publish_many_seconds": round(publish_s, 2),
        "speedup_vs_process_per_train": round(speedup_procs, 3),
        "speedup_vs_warm_inprocess": round(speedup_warm, 3),
        "bit_identical": all(identical),
        "bit_identical_per_model": identical,
        "published": len(published),
        "varied": ["learning_rate", "lambda_l2", "bagging_seed",
                   "bagging_fraction"],
        "note": "amortized wall-clock incl. all compiles on every "
                "side; the warm in-process baseline is CPU-pessimistic "
                "for the sweep (the batched pass pays real 16x FLOPs + "
                "batched-op overhead a TPU's MXU tile floor absorbs)",
    }
    record = {
        "metric": "sweep_vmapped_vs_sequential",
        "value": round(speedup_procs, 3),
        "unit": "x", "vs_baseline": 1.0, "detail": detail,
    }
    out_path = os.environ.get("BENCH_SWEEP_OUT",
                              os.path.join(REPO, "SWEEP_r01.json"))
    gate = {"ok": bool(all(identical) and speedup_procs >= 4.0),
            "speedup_floor": 4.0, **record}
    with open(out_path, "w") as fh:
        json.dump(gate, fh, indent=1)
    return [record]


# ---------------------------------------------------------------------------
# quantized-gradient training gate (BENCH_SHAPE=quantgrad, ISSUE 20)
# ---------------------------------------------------------------------------

def _quantgrad_config():
    rows = int(os.environ.get("BENCH_QG_ROWS", 10_000))
    feats = int(os.environ.get("BENCH_QG_FEATURES", 120))
    iters = int(os.environ.get("BENCH_QG_ITERS", 5))
    mc_rows = int(os.environ.get("BENCH_QG_MC_ROWS", 20_000))
    mc_iters = int(os.environ.get("BENCH_QG_MC_ITERS", 4))
    tol = float(os.environ.get("BENCH_QG_TOL", 0.5))
    # the wide-histogram shape: DENSE wide features x max_bin=255 (the
    # Epsilon builder at a tunable width — the Bosch builder's exclusive
    # blocks EFB-bundle away most of the table, which is exactly the
    # histogram mass this gate wants to keep)
    wide = {
        "objective": "binary", "verbosity": -1, "max_bin": 255,
        "num_leaves": 31, "learning_rate": 0.1, "min_data_in_leaf": 20,
        "tpu_hist_quantize_tol": tol,
    }
    mc = {
        "objective": "multiclass", "num_class": 5, "verbosity": -1,
        "max_bin": 63, "num_leaves": 31, "learning_rate": 0.1,
        "min_data_in_leaf": 20, "tpu_hist_quantize_tol": tol,
    }
    return rows, feats, iters, mc_rows, mc_iters, wide, mc


def _quantgrad_kernel_bench() -> dict:
    """Histogram-PASS throughput, f32 vs quantized, on the wide shape.

    The unit is leaf-histograms/s: one pass materializes ONE [chunk, G,
    B] one-hot operand shared by every leaf in the batch, and the batch
    is capped by the 128-lane output tile at C*S channels. int8's S=3
    (vs the bf16 hi+lo path's 5) fits 5/3 more leaves into the same
    pass — on CPU the contraction is memory-bound on that one-hot, so
    wall per pass barely moves while leaves-per-pass grows. (On an MXU
    the same tile-packing argument applies at the 128-lane floor; CPU
    numbers are the honest stand-in here.) int16 keeps S=5 (digit
    channels) and is expected ~1x — its win is exactness, not FLOPs."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.histogram import batched_leaves_histogram

    n = int(os.environ.get("BENCH_QG_KROWS", 16_384))
    g_feats = int(os.environ.get("BENCH_QG_KFEATURES", 120))
    bins = 255
    reps = int(os.environ.get("BENCH_QG_KREPS", 3))
    rng = np.random.RandomState(0)
    binned = jnp.asarray((rng.rand(n, g_feats) * bins).astype(np.uint8))
    leaf = jnp.asarray(rng.randint(0, 64, n).astype(np.int32))
    w = np.ones(n, np.float32)
    g = rng.randn(n).astype(np.float32)
    h = (rng.rand(n) + 0.1).astype(np.float32)
    qg = np.round(rng.randn(n) * 40).clip(-127, 127).astype(np.float32)
    qh = np.round(rng.rand(n) * 127).astype(np.float32)
    qg16 = np.round(rng.randn(n) * 9000).clip(-32767, 32767) \
        .astype(np.float32)
    qh16 = np.round(rng.rand(n) * 32767).astype(np.float32)
    mats = {
        "f32": jnp.asarray(np.stack([g * w, h * w, w], 1)),
        "int16": jnp.asarray(np.stack([qg16 * w, qh16 * w, w], 1)),
        "int8": jnp.asarray(np.stack([qg * w, qh * w, w], 1)),
    }
    # leaves per pass at the 128-lane tile: C * S <= 128
    batch = {"f32": 24, "int16": 24, "int8": 40}
    quant = {"f32": "none", "int16": "int16", "int8": "int8"}
    out = {}
    for mode in ("f32", "int16", "int8"):
        ids = jnp.arange(batch[mode], dtype=jnp.int32)

        def run():
            return batched_leaves_histogram(binned, mats[mode], leaf, ids,
                                            bins, quantize=quant[mode])

        run().block_until_ready()  # compile
        walls = []
        for _ in range(reps):
            t0 = time.time()
            run().block_until_ready()
            walls.append(time.time() - t0)
        best = min(walls)
        out[mode] = {
            "leaves_per_pass": batch[mode],
            "pass_seconds": round(best, 3),
            "leaf_hists_per_s": round(batch[mode] / best, 2),
        }
    base = out["f32"]["leaf_hists_per_s"]
    for mode in ("int16", "int8"):
        out[mode]["throughput_vs_f32"] = round(
            out[mode]["leaf_hists_per_s"] / base, 3)
    out["shape"] = {"rows": n, "features": g_feats, "max_bin": bins}
    return out


def _quantgrad_train_leg(X, y, params, iters, mode, cache_dir) -> dict:
    """One full-train leg: warmup round (compile), timed train, accuracy
    on the training rows, pass economics + compile-cache deltas."""
    import lightgbm_tpu as lgb

    p = dict(params, tpu_hist_quantize=mode)
    if cache_dir:
        p["tpu_compile_cache_dir"] = cache_dir
    ds = lgb.Dataset(X, y, params=dict(p))
    ds.construct()
    before = _cache_entries(cache_dir) if cache_dir else 0
    t0 = time.time()
    lgb.train(dict(p), ds, num_boost_round=1, verbose_eval=False)
    compile_s = time.time() - t0
    t0 = time.time()
    booster = lgb.train(dict(p), ds, num_boost_round=iters,
                        verbose_eval=False)
    booster.model_to_string()  # drain the dispatch pipeline
    wall = time.time() - t0
    pred = np.asarray(booster.predict(X))
    if p.get("objective") == "multiclass":
        acc = float((np.argmax(pred.reshape(len(y), -1), axis=1)
                     == y.astype(np.int64)).mean())
    else:
        acc = float(((pred > 0.5) == y.astype(bool)).mean())
    inner = booster._inner
    plog = getattr(inner, "pass_log", None) or []
    passes = (sum(pl[0] for pl in plog) / len(plog)) if plog else 0.0
    sched = getattr(inner, "_schedule_info", {})
    leg = {
        "mode": mode,
        "mrow_iters_per_s": round(len(y) * iters / wall / 1e6, 4),
        "wall_seconds": round(wall, 2),
        "compile_seconds": round(compile_s, 2),
        "train_accuracy": round(acc, 5),
        "passes_per_tree": round(passes, 1),
        "batch_k": sched.get("batch_k"),
    }
    if cache_dir:
        leg["compile_cache_new_entries"] = _cache_entries(cache_dir) - before
    return leg


def _quantgrad_comm_child(mode: str) -> None:
    """Comm-bytes probe under the scatter schedule, in a forced-device
    CPU child (same discipline as _multichip_child). Regression = a
    constant-hessian objective, so the quantized modes exercise the
    hessian-channel collective elision: 3 int32 channels -> 2 on the
    wire, visible as comm_bytes_per_pass (pass_log's 5th field).
    tpu_batch_k is pinned equal across modes: int8's automatic 5/3
    batch widening grows the per-pass payload (it trades passes for
    width), which would mask the per-leaf wire-format win this probe
    is after."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    import lightgbm_tpu as lgb

    rows = int(os.environ.get("BENCH_QG_COMM_ROWS", 20_000))
    iters = int(os.environ.get("BENCH_QG_COMM_ITERS", 3))
    X, y = synth_higgs(rows, N_FEATURES)
    y = np.asarray(X[:, 0] + 0.5 * X[:, 1] + 0.1 * y, np.float32)
    params = {
        "objective": "regression", "verbose": -1, "max_bin": MAX_BIN,
        "num_leaves": 31, "learning_rate": 0.1, "min_data_in_leaf": 20,
        "tree_learner": "data", "tpu_hist_reduce": "scatter",
        "tpu_hist_quantize": mode, "tpu_hist_quantize_tol": 10.0,
        "tpu_batch_k": int(os.environ.get("BENCH_QG_COMM_BATCH_K", 8)),
    }
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()
    t0 = time.time()
    booster = lgb.train(dict(params), ds, num_boost_round=iters,
                        verbose_eval=False)
    booster.model_to_string()
    wall = time.time() - t0
    inner = booster._inner
    plog = getattr(inner, "pass_log", None) or []
    passes = sum(pl[0] for pl in plog)
    comm_bytes = sum(float(pl[4]) for pl in plog if len(pl) > 4)
    sched = getattr(inner, "_schedule_info", {})
    print(json.dumps({
        "mode": mode,
        "comm_bytes_per_pass": round(comm_bytes / max(passes, 1)),
        "comm_bytes_per_tree": round(comm_bytes / max(len(plog), 1)),
        "passes_per_tree": round(passes / max(len(plog), 1), 1),
        "mrow_iters_per_s": round(rows * iters / wall / 1e6, 4),
        "hist_quantize": sched.get("hist_quantize"),
        "hess_const_elision": bool(sched.get("hist_hess_const")),
    }), flush=True)


def _quantgrad_warm_child() -> None:
    """Repeated-shape child: re-run the wide f32 leg's 1-round train
    against the SAME persistent compile cache the parent populated and
    report how much compiling was left to do (none, when the cache
    hit)."""
    import lightgbm_tpu as lgb

    rows, feats, _, _, _, wide, _ = _quantgrad_config()
    cache_dir = _bench_cache_dir()
    X, y = synth_epsilon(rows, feats)
    p = dict(wide, tpu_hist_quantize="none",
             tpu_compile_cache_dir=cache_dir)
    ds = lgb.Dataset(X, y, params=dict(p))
    ds.construct()
    before = _cache_entries(cache_dir)
    t0 = time.time()
    lgb.train(dict(p), ds, num_boost_round=1, verbose_eval=False)
    print(json.dumps({
        "compile_seconds": round(time.time() - t0, 2),
        "new_entries": _cache_entries(cache_dir) - before,
    }), flush=True)


def run_quantgrad() -> list:
    """Quantized-gradient training gate (BENCH_SHAPE=quantgrad): f32 vs
    int16 vs int8 on the wide-histogram shape (dense features x
    max_bin=255) and a 5-class multiclass shape. Reports Mrow-iters/s,
    the kernel-level histogram-pass throughput ratio (the >= 1.3x
    acceptance line), comm bytes/pass under the scatter schedule
    (hessian-channel elision), final train-accuracy delta vs f32, and
    the compile-cache hit/miss economics. Writes BENCH_QUANTGRAD_OUT
    (default QUANTGRAD_r01.json next to this file)."""
    import subprocess
    import sys

    rows, feats, iters, mc_rows, mc_iters, wide, mc = _quantgrad_config()
    cache_dir = None if os.environ.get("BENCH_NO_COMPILE_CACHE") == "1" \
        else _bench_cache_dir()
    backend = "cpu-fallback" if os.environ.get("BENCH_CPU_CHILD") == "1" \
        else "default"

    kernel = _quantgrad_kernel_bench()

    Xw, yw = synth_epsilon(rows, feats)
    Xm, ym = synth_multiclass(mc_rows)
    legs = {"wide": {}, "multiclass": {}}
    for mode in ("none", "int16", "int8"):
        legs["wide"][mode] = _quantgrad_train_leg(
            Xw, yw, dict(wide), iters, mode, cache_dir)
        legs["multiclass"][mode] = _quantgrad_train_leg(
            Xm, ym, dict(mc), mc_iters, mode, cache_dir)
    for shape in legs:
        base_acc = legs[shape]["none"]["train_accuracy"]
        for mode in ("int16", "int8"):
            legs[shape][mode]["accuracy_delta_vs_f32"] = round(
                legs[shape][mode]["train_accuracy"] - base_acc, 5)

    # scatter comm-bytes probe: forced-device children, f32 vs int8
    ndev = int(os.environ.get("BENCH_QG_COMM_DEVICES", 4))
    comm = {}
    for mode in ("none", "int8"):
        env = dict(os.environ)
        env["BENCH_QUANTGRAD_COMM_CHILD"] = mode
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + f" --xla_force_host_platform_device_count"
                            f"={ndev}").strip()
        res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=float(os.environ.get(
                                 "BENCH_QG_COMM_TIMEOUT", 900)))
        line = next((ln for ln in res.stdout.splitlines()
                     if ln.startswith("{")), None)
        if res.returncode != 0 or line is None:
            comm[mode] = {"error": (res.stdout + res.stderr)[-400:]}
        else:
            comm[mode] = json.loads(line)
    comm_ratio = None
    if "comm_bytes_per_pass" in comm.get("none", {}) \
            and comm.get("int8", {}).get("comm_bytes_per_pass"):
        comm_ratio = round(comm["none"]["comm_bytes_per_pass"]
                           / comm["int8"]["comm_bytes_per_pass"], 3)

    # repeated-shape child against the parent's populated cache
    cache_probe = None
    if cache_dir:
        env = dict(os.environ)
        env["BENCH_QUANTGRAD_WARM_CHILD"] = "1"
        res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=600)
        line = next((ln for ln in res.stdout.splitlines()
                     if ln.startswith("{")), None)
        if res.returncode == 0 and line:
            cache_probe = json.loads(line)
            cache_probe["cold_compile_seconds"] = \
                legs["wide"]["none"]["compile_seconds"]
            cache_probe["hit"] = cache_probe["new_entries"] == 0

    kernel_ratio = kernel["int8"]["throughput_vs_f32"]
    acc_ok = all(
        abs(legs[shape][mode]["accuracy_delta_vs_f32"]) <= 0.02
        for shape in legs for mode in ("int16", "int8"))
    detail = {
        "backend": backend,
        "wide_shape": {"rows": rows, "features": feats, "max_bin": 255,
                       "iters": iters},
        "multiclass_shape": {"rows": mc_rows, "features": 28, "classes": 5,
                             "max_bin": 63, "iters": mc_iters},
        "kernel_pass_throughput": kernel,
        "train": legs,
        "scatter_comm": {"devices": ndev, **comm,
                         "bytes_ratio_f32_over_int8": comm_ratio},
        "compile_cache_probe": cache_probe,
        "note": "CPU numbers: the int8 kernel win is tile/operand "
                "packing (5/3 more leaves per one-hot pass), not FLOP "
                "rate — on an MXU the same packing argument applies at "
                "the 128-lane output-tile floor. int16 is ~1x by "
                "design (5 digit channels); its payoff is exact int32 "
                "schedule-invariant histograms.",
    }
    record = {
        "metric": "quantgrad_int8_hist_pass_throughput",
        "value": kernel_ratio,
        "unit": "x_vs_f32", "vs_baseline": 1.3,
        "detail": detail,
    }
    gate = {"ok": bool(kernel_ratio >= 1.3 and acc_ok
                       and (comm_ratio or 0) >= 1.2),
            "kernel_ratio_floor": 1.3, "comm_ratio_floor": 1.2,
            "accuracy_delta_ceiling": 0.02, **record}
    out_path = os.environ.get("BENCH_QUANTGRAD_OUT",
                              os.path.join(REPO, "QUANTGRAD_r01.json"))
    with open(out_path, "w") as fh:
        json.dump(gate, fh, indent=1)
    return [record]


def _run_smoke_gate(script_name: str, out_path: str, timeout_env: str,
                    metric: str, extra_args=(), extra_env=None) -> dict:
    """Shared child-gate runner for the smoke-script shapes (elastic,
    overload): unlink the stale committed artifact (it must not
    masquerade as this run's result when the smoke dies before
    writing), run the script in a child with an env-tunable timeout,
    and report the artifact (or the output tail on failure) as the
    metric detail. The parent never touches a backend."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", script_name)
    try:
        os.unlink(out_path)
    except OSError:
        pass
    env = dict(os.environ)
    env.update(extra_env or {})
    cmd = [sys.executable, script, "--out", out_path] + list(extra_args)
    try:
        res = subprocess.run(
            cmd, env=env, capture_output=True, text=True,
            timeout=float(os.environ.get(timeout_env, 900)))
        rc, tail = res.returncode, (res.stdout + res.stderr)[-800:]
    except subprocess.TimeoutExpired as exc:
        rc, tail = 124, "timeout: " + str(exc)
    try:
        with open(out_path) as fh:
            detail = json.load(fh)
    except (OSError, json.JSONDecodeError):
        detail = {"error": tail}
    return {"metric": metric, "value": 1.0 if rc == 0 else 0.0,
            "unit": "ok", "rc": rc, "detail": detail}


def run_elastic() -> dict:
    """Elasticity gate (BENCH_SHAPE=elastic): run the supervisor's
    kill -> detect -> shrink -> resume cycle headlessly and commit the
    machine-readable artifact (ELASTIC_r01.json: ranks killed,
    detection latency, resume outcome, byte-identity verdict). The
    parent never touches a backend — every world size runs in its own
    child (the multichip-gate discipline)."""
    return _run_smoke_gate(
        "elastic_smoke.py",
        os.environ.get("BENCH_ELASTIC_OUT",
                       os.path.join(REPO, "ELASTIC_r01.json")),
        "BENCH_ELASTIC_TIMEOUT", "elastic_kill_shrink_resume",
        extra_args=["--mode",
                    os.environ.get("BENCH_ELASTIC_MODE", "devices")])


def run_lint() -> dict:
    """Static-analysis gate (BENCH_SHAPE=lint): run graftlint over the
    package + scripts in a child (no backend involved) and commit the
    machine-readable artifact (LINT_r01.json: per-rule counts, zero
    unsuppressed findings, suppressions with their written reasons)."""
    return _run_smoke_gate(
        "lint_report.py",
        os.environ.get("BENCH_LINT_OUT",
                       os.path.join(REPO, "LINT_r01.json")),
        "BENCH_LINT_TIMEOUT", "lint_zero_unsuppressed_findings")


def run_overload() -> dict:
    """Overload-resilience gate (BENCH_SHAPE=overload): run the serving
    tier's admission/shedding/breaker/cold-start smoke headlessly and
    commit the machine-readable artifact (OVERLOAD_r01.json: open-loop
    bench at ~2x saturation with bounded admitted p99 + structured
    rejections, breaker trip/recovery, single-flight compile storm,
    persistent-compile-cache cold start). BENCH_ALLOW_CPU=1 pins the
    child to the CPU backend, the serve/elastic-gate discipline."""
    return _run_smoke_gate(
        "overload_smoke.py",
        os.environ.get("BENCH_OVERLOAD_OUT",
                       os.path.join(REPO, "OVERLOAD_r01.json")),
        "BENCH_OVERLOAD_TIMEOUT", "overload_shed_breaker_coldstart",
        extra_env={"JAX_PLATFORMS": "cpu"}
        if os.environ.get("BENCH_ALLOW_CPU") == "1" else None)


def run_chaos() -> dict:
    """Storage-fault-tolerance gate (BENCH_SHAPE=chaos): run the
    durable-IO chaos smoke headlessly and commit the machine-readable
    artifact (CHAOS_r01.json: byte-identity under injected
    EIO/torn/slow-IO, per-stream degradation counts, ENOSPC eviction
    hatch). The parent never touches a backend — both training runs and
    the hatch stage live in their own CPU-pinned children."""
    return _run_smoke_gate(
        "storage_chaos_smoke.py",
        os.environ.get("BENCH_CHAOS_OUT",
                       os.path.join(REPO, "CHAOS_r01.json")),
        "BENCH_CHAOS_TIMEOUT", "storage_chaos_byte_identity")


def run_export() -> dict:
    """Exported-forest gate (BENCH_SHAPE=export): run the artifact
    round-trip / refusal / import-blocked-cold-serve smoke headlessly
    and commit the machine-readable artifact (EXPORT_r01.json:
    per-layout bit-identity, refusal messages, child trainer-absence +
    zero-retrace verdict). BENCH_ALLOW_CPU=1 pins the child to the CPU
    backend, the serve/elastic/overload-gate discipline."""
    return _run_smoke_gate(
        "export_smoke.py",
        os.environ.get("BENCH_EXPORT_OUT",
                       os.path.join(REPO, "EXPORT_r01.json")),
        "BENCH_EXPORT_TIMEOUT", "export_roundtrip_refusal_coldserve",
        extra_env={"JAX_PLATFORMS": "cpu"}
        if os.environ.get("BENCH_ALLOW_CPU") == "1" else None)


def run_linear() -> dict:
    """Piecewise-linear leaves gate (BENCH_SHAPE=linear): on a shape
    with regional linear structure — four quadrant regions, each with
    its own plane — train a constant-leaf booster for the full budget,
    then ask at which iteration a linear_tree booster (same schedule
    otherwise) first reaches the constant run's FINAL holdout l2.

    Acceptance: iterations-to-target ratio <= 0.7 (the 1802.05640
    claim this subsystem exists for), reported alongside the honest
    trees/s overhead of the extra per-tree fit program. Commits
    BENCH_LINEAR_OUT (default LINEAR_r01.json next to this file)."""
    import lightgbm_tpu as lgb

    rows = int(os.environ.get("BENCH_LINEAR_ROWS", 20000))
    iters = int(os.environ.get("BENCH_LINEAR_ITERS", 60))
    feats = 10
    rng = np.random.RandomState(11)
    X = rng.uniform(-1.0, 1.0, (rows, feats))
    region = (X[:, 0] > 0).astype(int) * 2 + (X[:, 1] > 0).astype(int)
    planes = rng.randn(4, feats)
    bias = 2.0 * rng.randn(4)
    y = (planes[region] * X).sum(axis=1) + bias[region] \
        + 0.05 * rng.randn(rows)
    n_tr = int(rows * 0.8)

    def _one(linear: bool):
        # no valid sets: both legs ride their fast training path (the
        # per-iteration valid replay would dominate and measure the
        # wrong thing); the holdout curve is probed post-hoc
        params = {"objective": "regression",
                  "num_leaves": 31, "learning_rate": 0.1,
                  "min_data_in_leaf": 20, "verbose": -1,
                  "max_bin": MAX_BIN,
                  "linear_tree": linear, "linear_lambda": 0.01}
        ds = lgb.Dataset(X[:n_tr], label=y[:n_tr], params=params)
        t0 = time.time()
        bst = lgb.train(params, ds, num_boost_round=iters,
                        verbose_eval=False)
        return bst, time.time() - t0

    def _l2(bst, i):
        pred = bst.predict(X[n_tr:], num_iteration=i)
        return float(np.mean((pred - y[n_tr:]) ** 2))

    const_bst, const_wall = _one(False)
    linear_bst, linear_wall = _one(True)
    target = _l2(const_bst, iters)
    linear_final = _l2(linear_bst, iters)
    # first linear iteration reaching the constant run's final l2,
    # by bisection (holdout l2 is effectively monotone at lr 0.1 on
    # this shape, far from overfit)
    hit = None
    if linear_final <= target:
        lo, hi = 1, iters
        while lo < hi:
            mid = (lo + hi) // 2
            if _l2(linear_bst, mid) <= target:
                hi = mid
            else:
                lo = mid + 1
        hit = lo
    ratio = (hit / float(iters)) if hit is not None else float("inf")
    overhead = linear_wall / max(const_wall, 1e-9)
    detail = {
        "rows": rows, "features": feats, "iterations": iters,
        "holdout_rows": rows - n_tr,
        "constant_final_l2": round(target, 6),
        "linear_final_l2": round(linear_final, 6),
        "linear_iters_to_constant_final": hit,
        "iters_ratio": round(ratio, 4) if hit is not None else None,
        "constant_train_seconds": round(const_wall, 2),
        "linear_train_seconds": round(linear_wall, 2),
        "linear_trees_per_s": round(iters / max(linear_wall, 1e-9), 2),
        "constant_trees_per_s": round(iters / max(const_wall, 1e-9), 2),
        "wall_overhead": round(overhead, 3),
        "note": "wall includes compiles on both sides; the linear leg "
                "pays one extra traced program (post-growth ridge fit) "
                "per signature plus the per-tree fit dispatch",
    }
    record = {
        "metric": "linear_tree_iters_to_constant_final",
        "value": round(ratio, 4) if hit is not None else -1.0,
        "unit": "ratio", "vs_baseline": 0.7, "detail": detail,
    }
    gate = {"ok": bool(hit is not None and ratio <= 0.7),
            "ratio_ceiling": 0.7, **record}
    out_path = os.environ.get("BENCH_LINEAR_OUT",
                              os.path.join(REPO, "LINEAR_r01.json"))
    with open(out_path, "w") as fh:
        json.dump(gate, fh, indent=1)
    return record


def main():
    if os.environ.get("BENCH_SWEEP_CHILD") is not None \
            and os.environ.get("BENCH_SWEEP_MODEL_OUT"):
        _sweep_child()
        return
    if os.environ.get("BENCH_MULTICHIP_CHILD"):
        _multichip_child(int(os.environ["BENCH_MULTICHIP_CHILD"]))
        return
    if os.environ.get("BENCH_QUANTGRAD_COMM_CHILD"):
        _quantgrad_comm_child(os.environ["BENCH_QUANTGRAD_COMM_CHILD"])
        return
    if os.environ.get("BENCH_QUANTGRAD_WARM_CHILD"):
        _quantgrad_warm_child()
        return
    if os.environ.get("BENCH_INGEST_CHILD"):
        _ingest_child(os.environ["BENCH_INGEST_CHILD"],
                      os.environ["BENCH_INGEST_PATH"],
                      int(os.environ["BENCH_INGEST_ROWS"]))
        return
    which = os.environ.get("BENCH_SHAPE", "higgs")
    if which == "multichip":
        # the parent never touches a backend: each device count runs in
        # a child pinned to the CPU platform (same rationale as the
        # dryrun gate — a dead TPU relay must not hang the harness)
        for entry in run_multichip():
            print(json.dumps(entry), flush=True)
        return
    if which == "lint":
        # pure source analysis in a child; the parent (and the child)
        # never need a backend
        print(json.dumps(run_lint()), flush=True)
        return
    if which == "elastic":
        print(json.dumps(run_elastic()), flush=True)
        return
    if which == "overload":
        # same parent-never-touches-a-backend discipline as elastic:
        # the smoke runs in its own child process
        print(json.dumps(run_overload()), flush=True)
        return
    if which == "export":
        print(json.dumps(run_export()), flush=True)
        return
    if which == "chaos":
        # storage chaos: same parent-never-touches-a-backend discipline
        print(json.dumps(run_chaos()), flush=True)
        return
    _init_backend_with_retry()
    if which == "linear":
        print(json.dumps(run_linear()), flush=True)
        return
    if which == "amortized":
        print(json.dumps(run_amortized()), flush=True)
        return
    if which == "predict":
        for entry in run_predict():
            print(json.dumps(entry), flush=True)
        return
    if which == "serve":
        for entry in run_serve():
            print(json.dumps(entry), flush=True)
        return
    if which == "sweep":
        for entry in run_sweep():
            print(json.dumps(entry), flush=True)
        return
    if which == "quantgrad":
        for entry in run_quantgrad():
            print(json.dumps(entry), flush=True)
        return
    if which == "ingest":
        for entry in run_ingest():
            print(json.dumps(entry), flush=True)
        return
    names = list(SHAPES) if which == "all" else [which]
    for name in names:
        print(json.dumps(run_shape(name)), flush=True)


if __name__ == "__main__":
    main()
