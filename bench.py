"""Benchmark: HIGGS-like GBDT training throughput on the local accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Setup mirrors the reference's published benchmark config
(docs/GPU-Performance.md:96-116 / BASELINE.md): max_bin=63, num_leaves=255,
lr=0.1, min_data_in_leaf=1, min_sum_hessian_in_leaf=100, binary objective,
dense ~28-feature data (HIGGS is 10.5M x 28; we bench a scaled-down slice
sized for CI-time runs and report million-rows-processed/sec so the number
is size-invariant).

vs_baseline: the reference repo publishes no wall-clock numbers
(BASELINE.md: chart is an external image), so the baseline constant below
is the reference CPU implementation measured on this machine via
scripts/measure_baseline.py (which builds /root/reference out-of-tree) and
cached in BENCH_BASELINE.json; falls back to 1.0 (self-relative) if absent.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 2_000_000))
N_FEATURES = 28
N_ITERS = int(os.environ.get("BENCH_ITERS", 15))
NUM_LEAVES = 255
MAX_BIN = 63


def synth_higgs(n, f, seed=0):
    """Synthetic HIGGS-like: dense float features, binary label from a
    nonlinear score (matches HIGGS's structure: 28 kinematic features)."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    score = (X[:, 0] * 1.2 - X[:, 1] + 0.8 * X[:, 2] * X[:, 3]
             + 0.5 * np.abs(X[:, 4]) + 0.3 * X[:, 5] ** 2)
    y = (score + rng.logistic(size=n) > 0.5).astype(np.float32)
    return X, y


def main():
    import lightgbm_tpu as lgb

    X, y = synth_higgs(N_ROWS, N_FEATURES)
    params = {
        "objective": "binary", "metric": "auc", "verbose": -1,
        "max_bin": MAX_BIN, "num_leaves": NUM_LEAVES,
        "learning_rate": 0.1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0,
    }
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()

    # warmup: compile the grower (first tree)
    t0 = time.time()
    warm = lgb.train(dict(params), ds, num_boost_round=1, verbose_eval=False)
    compile_time = time.time() - t0

    # per-iteration wall times via callback; the first timed iteration
    # carries the per-run jit trace (the reference C++ has no compile
    # analogue and its published benchmarks run 500 iters, where one
    # trace amortizes to noise) — report BOTH with/without it
    iter_times = []
    last = [None]

    def _timer(env):
        now = time.time()
        if last[0] is not None:
            iter_times.append(now - last[0])
        last[0] = now

    t0 = time.time()
    booster = lgb.train(dict(params), ds, num_boost_round=N_ITERS,
                        verbose_eval=False, callbacks=[_timer])
    train_time = time.time() - t0

    steady = iter_times[1:] if len(iter_times) > 2 else iter_times
    steady_time = sum(steady) / len(steady) if steady \
        else train_time / N_ITERS
    rows_per_sec = N_ROWS / steady_time
    value = rows_per_sec / 1e6  # million row-iterations per second
    value_incl_trace = N_ROWS * N_ITERS / train_time / 1e6

    baseline = None
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        with open(base_path) as fh:
            b = json.load(fh)
            baseline = b.get("mrows_per_sec")
    vs_baseline = (value / baseline) if baseline else 1.0

    print(json.dumps({
        "metric": "higgs_like_train_throughput",
        "value": round(value, 4),
        "unit": "mrow_iters/s",
        "vs_baseline": round(vs_baseline, 4),
        "detail": {
            "rows": N_ROWS, "features": N_FEATURES, "iters": N_ITERS,
            "num_leaves": NUM_LEAVES, "max_bin": MAX_BIN,
            "train_seconds": round(train_time, 3),
            "compile_seconds": round(compile_time, 3),
            "steady_seconds_per_iter": round(steady_time, 4),
            "mrow_iters_incl_trace": round(value_incl_trace, 4),
        },
    }))


if __name__ == "__main__":
    main()
