"""Generate the example datasets (the reference ships ~7MB of data files;
this repo generates statistically-similar synthetic stand-ins so the
train.conf files run unmodified).

Usage: python examples/gen_data.py
"""
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def write(path, y, X, fmt="%.6g"):
    np.savetxt(path, np.column_stack([y, X]), delimiter="\t", fmt=fmt)
    print(path, X.shape)


def binary(n=7000, f=28, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    s = X[:, 0] * 1.2 - X[:, 1] + 0.8 * X[:, 2] * X[:, 3] + 0.5 * np.abs(X[:, 4])
    y = (s + rng.logistic(size=n) > 0.3).astype(int)
    d = os.path.join(HERE, "binary_classification")
    os.makedirs(d, exist_ok=True)
    s = min(5000, int(n * 0.7))
    write(os.path.join(d, "binary.train"), y[:s], X[:s])
    write(os.path.join(d, "binary.test"), y[s:], X[s:])


def regression(n=7000, f=20, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] * 2 + X[:, 1] ** 2 - X[:, 2] * X[:, 3] + 0.3 * rng.randn(n)
    d = os.path.join(HERE, "regression")
    os.makedirs(d, exist_ok=True)
    s = min(5000, int(n * 0.7))
    write(os.path.join(d, "regression.train"), y[:s], X[:s])
    write(os.path.join(d, "regression.test"), y[s:], X[s:])


def lambdarank(n_queries=250, seed=2):
    rng = np.random.RandomState(seed)
    rows, sizes = [], []
    for _ in range(n_queries):
        c = rng.randint(5, 40)
        sizes.append(c)
        Xq = rng.randn(c, 16)
        rel = np.clip(Xq[:, 0] * 1.5 + 0.4 * rng.randn(c), 0, None)
        yq = np.minimum(rel.astype(int), 4)
        rows.append(np.column_stack([yq, Xq]))
    arr = np.vstack(rows)
    split_q = int(n_queries * 0.8)
    split_r = int(np.cumsum(sizes)[split_q - 1])
    d = os.path.join(HERE, "lambdarank")
    np.savetxt(os.path.join(d, "rank.train"), arr[:split_r], delimiter="\t", fmt="%.6g")
    np.savetxt(os.path.join(d, "rank.test"), arr[split_r:], delimiter="\t", fmt="%.6g")
    with open(os.path.join(d, "rank.train.query"), "w") as fh:
        fh.write("\n".join(str(s) for s in sizes[:split_q]))
    with open(os.path.join(d, "rank.test.query"), "w") as fh:
        fh.write("\n".join(str(s) for s in sizes[split_q:]))
    print(os.path.join(d, "rank.train"), arr.shape)


def parallel(seed=3):
    # same shape as binary_classification; both machines read the same
    # file and the loader partitions rows by rank
    rng = np.random.RandomState(seed)
    n, f = 4000, 12
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    d = os.path.join(HERE, "parallel_learning")
    write(os.path.join(d, "binary.train"), y, X)


def multiclass(n=7000, f=28, k=5, seed=4):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    centers = rng.randn(k, 6) * 1.5
    dist = ((X[:, None, :6] - centers[None]) ** 2).sum(-1)
    y = np.argmin(dist + rng.gumbel(size=(n, k)), axis=1).astype(int)
    d = os.path.join(HERE, "multiclass_classification")
    os.makedirs(d, exist_ok=True)
    s = min(5000, int(n * 0.7))
    write(os.path.join(d, "multiclass.train"), y[:s], X[:s])
    write(os.path.join(d, "multiclass.test"), y[s:], X[s:])


if __name__ == "__main__":
    binary()
    regression()
    lambdarank()
    parallel()
    multiclass()
