"""Basic train -> evaluate -> save -> predict loop on the regression
example data (reference analogue: examples/python-guide/simple_example.py)."""
import os

import numpy as np

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))
REG = os.path.join(HERE, "..", "regression")

train = np.loadtxt(os.path.join(REG, "regression.train"), delimiter="\t")
test = np.loadtxt(os.path.join(REG, "regression.test"), delimiter="\t")
y_train, X_train = train[:, 0], train[:, 1:]
y_test, X_test = test[:, 0], test[:, 1:]

lgb_train = lgb.Dataset(X_train, y_train)
lgb_eval = lgb.Dataset(X_test, y_test, reference=lgb_train)

params = {
    "boosting_type": "gbdt",
    "objective": "regression",
    "metric": ["l2", "l1"],
    "num_leaves": 31,
    "learning_rate": 0.05,
    "feature_fraction": 0.9,
    "bagging_fraction": 0.8,
    "bagging_freq": 5,
    "verbose": 0,
}

print("Starting training...")
gbm = lgb.train(params, lgb_train, num_boost_round=20,
                valid_sets=[lgb_eval], early_stopping_rounds=5)

print("Saving model...")
gbm.save_model(os.path.join(HERE, "model.txt"))

print("Starting predicting...")
y_pred = gbm.predict(X_test, num_iteration=gbm.best_iteration)
rmse = float(np.sqrt(np.mean((y_pred - y_test) ** 2)))
print(f"The RMSE of prediction is: {rmse}")
