"""Plotting helpers: metric curves, importance, a single tree
(reference analogue: examples/python-guide/plot_example.py). Skips
gracefully when matplotlib is unavailable."""
import os

import numpy as np

import lightgbm_tpu as lgb

try:
    import matplotlib  # noqa: F401
    matplotlib.use("Agg")
except ImportError:
    raise SystemExit("matplotlib is not installed; nothing to plot")

HERE = os.path.dirname(os.path.abspath(__file__))
REG = os.path.join(HERE, "..", "regression")

train = np.loadtxt(os.path.join(REG, "regression.train"), delimiter="\t")
test = np.loadtxt(os.path.join(REG, "regression.test"), delimiter="\t")
y_train, X_train = train[:, 0], train[:, 1:]
y_test, X_test = test[:, 0], test[:, 1:]

lgb_train = lgb.Dataset(X_train, y_train)
lgb_eval = lgb.Dataset(X_test, y_test, reference=lgb_train)

evals_result = {}
gbm = lgb.train({"objective": "regression", "metric": "l2",
                 "verbose": 0}, lgb_train, num_boost_round=20,
                valid_sets=[lgb_train, lgb_eval],
                valid_names=["train", "valid"],
                evals_result=evals_result, verbose_eval=False)

print("Plotting metrics during training...")
ax = lgb.plot_metric(evals_result, metric="l2")
ax.figure.savefig(os.path.join(HERE, "metric.png"))

print("Plotting feature importances...")
ax = lgb.plot_importance(gbm, max_num_features=10)
ax.figure.savefig(os.path.join(HERE, "importance.png"))

print("Plotting the first tree...")
ax = lgb.plot_tree(gbm, tree_index=0)
ax.figure.savefig(os.path.join(HERE, "tree.png"))
print("wrote metric.png importance.png tree.png")
