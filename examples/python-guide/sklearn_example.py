"""scikit-learn estimator API + GridSearchCV (reference analogue:
examples/python-guide/sklearn_example.py)."""
import os

import numpy as np

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))
REG = os.path.join(HERE, "..", "regression")

train = np.loadtxt(os.path.join(REG, "regression.train"), delimiter="\t")
test = np.loadtxt(os.path.join(REG, "regression.test"), delimiter="\t")
y_train, X_train = train[:, 0], train[:, 1:]
y_test, X_test = test[:, 0], test[:, 1:]

print("Starting training...")
gbm = lgb.LGBMRegressor(objective="regression", num_leaves=31,
                        learning_rate=0.05, n_estimators=20)
gbm.fit(X_train, y_train, eval_set=[(X_test, y_test)],
        eval_metric="l1", early_stopping_rounds=5)

print("Starting predicting...")
y_pred = gbm.predict(X_test, num_iteration=gbm.best_iteration_)
rmse = float(np.sqrt(np.mean((y_pred - y_test) ** 2)))
print(f"The RMSE of prediction is: {rmse}")

print("Feature importances:", list(gbm.feature_importances_))

try:
    from sklearn.model_selection import GridSearchCV
    estimator = lgb.LGBMRegressor()
    param_grid = {"learning_rate": [0.01, 0.1], "n_estimators": [10, 20]}
    gbm = GridSearchCV(estimator, param_grid, cv=3)
    gbm.fit(X_train, y_train)
    print("Best parameters found by grid search are:", gbm.best_params_)
except ImportError:
    print("sklearn not available; skipping grid search")
