"""Advanced API features: weights, init score, continued training,
JSON dump, importance (reference analogue:
examples/python-guide/advanced_example.py)."""
import json
import os

import numpy as np

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))
BIN = os.path.join(HERE, "..", "binary_classification")

train = np.loadtxt(os.path.join(BIN, "binary.train"), delimiter="\t")
test = np.loadtxt(os.path.join(BIN, "binary.test"), delimiter="\t")
y_train, X_train = train[:, 0], train[:, 1:]
y_test, X_test = test[:, 0], test[:, 1:]
n = len(y_train)

# per-row weights
w = np.where(np.arange(n) % 3 == 0, 0.5, 1.0)
lgb_train = lgb.Dataset(X_train, y_train, weight=w, free_raw_data=False)
lgb_eval = lgb.Dataset(X_test, y_test, reference=lgb_train)

params = {"boosting_type": "gbdt", "objective": "binary",
          "metric": "binary_logloss", "num_leaves": 31, "verbose": 0}

evals_result = {}
gbm = lgb.train(params, lgb_train, num_boost_round=10,
                valid_sets=[lgb_eval], evals_result=evals_result,
                verbose_eval=5)

print("Dumping model to JSON...")
model_json = gbm.dump_model()
with open(os.path.join(HERE, "model.json"), "w") as fh:
    json.dump(model_json, fh, indent=2)

print("Feature importances:", list(gbm.feature_importance()))

print("Saving and continuing training from the saved model...")
path = os.path.join(HERE, "model_adv.txt")
gbm.save_model(path)
gbm2 = lgb.train(params, lgb_train, num_boost_round=10,
                 init_model=path, valid_sets=[lgb_eval],
                 verbose_eval=False)
print("Continued model has", gbm2.num_trees(), "trees")

print("Prediction with early stopping:")
pred = gbm2.predict(X_test, pred_early_stop=True,
                    pred_early_stop_freq=5, pred_early_stop_margin=4.0)
print("first 5 predictions:", pred[:5])
